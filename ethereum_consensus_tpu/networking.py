"""Networking identity types and gossip constants.

Reference parity: ethereum-consensus/src/networking.rs (~160 LoC) — `PeerId`
reimplemented over base58(multihash) (networking.rs:13), `Multiaddr`, `Enr`
alias, gossip `MessageDomain`; per-fork constants from
src/{phase0,altair,deneb}/networking.rs. Pure from-scratch implementations —
no libp2p dependency.
"""

from __future__ import annotations

from enum import Enum

from .ssz import Bitvector, Container, uint64

__all__ = [
    "MAX_INLINE_KEY_LENGTH",
    "PeerId",
    "Multiaddr",
    "Enr",
    "MessageDomain",
    "ATTESTATION_SUBNET_COUNT",
    "GOSSIP_MAX_SIZE",
    "MAX_REQUEST_BLOCKS",
    "MIN_EPOCHS_FOR_BLOCK_REQUESTS",
    "MAX_CHUNK_SIZE",
    "TTFB_TIMEOUT",
    "RESP_TIMEOUT",
    "ATTESTATION_PROPAGATION_SLOT_RANGE",
    "MAXIMUM_GOSSIP_CLOCK_DISPARITY",
    "MetaData",
    "MetaDataAltair",
    "MAX_REQUEST_BLOCKS_DENEB",
    "MAX_REQUEST_BLOB_SIDECARS",
    "MIN_EPOCHS_FOR_BLOB_SIDECARS_REQUESTS",
    "BLOB_SIDECAR_SUBNET_COUNT",
]

MAX_INLINE_KEY_LENGTH = 42

_B58_ALPHABET = "123456789ABCDEFGHJKLMNPQRSTUVWXYZabcdefghijkmnopqrstuvwxyz"
_B58_INDEX = {c: i for i, c in enumerate(_B58_ALPHABET)}

# multihash codes accepted for peer ids (networking.rs:38-44)
_MH_IDENTITY = 0x00
_MH_SHA2_256 = 0x12


def _b58encode(data: bytes) -> str:
    n = int.from_bytes(data, "big")
    out = []
    while n:
        n, rem = divmod(n, 58)
        out.append(_B58_ALPHABET[rem])
    pad = 0
    for b in data:
        if b == 0:
            pad += 1
        else:
            break
    return "1" * pad + "".join(reversed(out))


def _b58decode(text: str) -> bytes:
    n = 0
    for c in text:
        if c not in _B58_INDEX:
            raise ValueError(f"invalid base58 character {c!r}")
        n = n * 58 + _B58_INDEX[c]
    raw = n.to_bytes((n.bit_length() + 7) // 8, "big")
    pad = 0
    for c in text:
        if c == "1":
            pad += 1
        else:
            break
    return b"\x00" * pad + raw


def _varint_encode(n: int) -> bytes:
    out = bytearray()
    while True:
        byte = n & 0x7F
        n >>= 7
        if n:
            out.append(byte | 0x80)
        else:
            out.append(byte)
            return bytes(out)


def _varint_decode(data: bytes, offset: int = 0) -> tuple[int, int]:
    shift = 0
    value = 0
    while True:
        if offset >= len(data):
            raise ValueError("truncated varint")
        byte = data[offset]
        value |= (byte & 0x7F) << shift
        offset += 1
        if not byte & 0x80:
            return value, offset
        shift += 7


class PeerId:
    """libp2p peer id: base58(multihash) (networking.rs:13)."""

    __slots__ = ("code", "digest")

    def __init__(self, code: int, digest: bytes):
        if code == _MH_SHA2_256:
            pass
        elif code == _MH_IDENTITY and len(digest) <= MAX_INLINE_KEY_LENGTH:
            pass
        else:
            raise ValueError(f"unsupported multihash code {code:#x} for PeerId")
        self.code = code
        self.digest = bytes(digest)

    def to_bytes(self) -> bytes:
        return _varint_encode(self.code) + _varint_encode(len(self.digest)) + self.digest

    @classmethod
    def from_bytes(cls, data: bytes) -> "PeerId":
        code, offset = _varint_decode(data)
        size, offset = _varint_decode(data, offset)
        digest = data[offset : offset + size]
        if len(digest) != size or offset + size != len(data):
            raise ValueError("malformed multihash")
        return cls(code, digest)

    def to_base58(self) -> str:
        return _b58encode(self.to_bytes())

    @classmethod
    def from_str(cls, text: str) -> "PeerId":
        return cls.from_bytes(_b58decode(text))

    def __str__(self) -> str:
        return self.to_base58()

    def __repr__(self) -> str:
        return f"PeerId({self.to_base58()!r})"

    def __eq__(self, other) -> bool:
        return (
            isinstance(other, PeerId)
            and self.code == other.code
            and self.digest == other.digest
        )

    def __hash__(self) -> int:
        return hash((self.code, self.digest))


class Multiaddr:
    """Opaque multiaddr (string form), sufficient for API presentation."""

    __slots__ = ("value",)

    def __init__(self, value: str):
        if not value.startswith("/"):
            raise ValueError("multiaddr must start with '/'")
        self.value = value

    def __str__(self) -> str:
        return self.value

    def __repr__(self) -> str:
        return f"Multiaddr({self.value!r})"

    def __eq__(self, other) -> bool:
        return isinstance(other, Multiaddr) and self.value == other.value

    def __hash__(self) -> int:
        return hash(self.value)


# ENR: presented as its textual "enr:..." form (networking.rs Enr alias)
Enr = str


class MessageDomain(Enum):
    """Gossip message-id domains (networking.rs MessageDomain)."""

    INVALID_SNAPPY = b"\x00\x00\x00\x00"
    VALID_SNAPPY = b"\x01\x00\x00\x00"


# -- phase0 gossip constants (phase0/networking.rs) --------------------------
ATTESTATION_SUBNET_COUNT = 64
GOSSIP_MAX_SIZE = 2**20
MAX_REQUEST_BLOCKS = 2**10
MIN_EPOCHS_FOR_BLOCK_REQUESTS = 33024
MAX_CHUNK_SIZE = 2**20
TTFB_TIMEOUT = 5.0  # seconds
RESP_TIMEOUT = 10.0  # seconds
ATTESTATION_PROPAGATION_SLOT_RANGE = 32
MAXIMUM_GOSSIP_CLOCK_DISPARITY = 0.5  # seconds


class MetaData(Container):
    """(phase0/networking.rs MetaData)"""

    seq_number: uint64
    attnets: Bitvector[ATTESTATION_SUBNET_COUNT]


# altair adds sync-committee subnets (altair/networking.rs)
from .models.altair.constants import SYNC_COMMITTEE_SUBNET_COUNT  # noqa: E402


class MetaDataAltair(Container):
    """(altair/networking.rs MetaData)"""

    seq_number: uint64
    attnets: Bitvector[ATTESTATION_SUBNET_COUNT]
    syncnets: Bitvector[SYNC_COMMITTEE_SUBNET_COUNT]


# -- deneb blob gossip constants (deneb/networking.rs) -----------------------
MAX_REQUEST_BLOCKS_DENEB = 2**7
MAX_REQUEST_BLOB_SIDECARS = 768
MIN_EPOCHS_FOR_BLOB_SIDECARS_REQUESTS = 2**12
BLOB_SIDECAR_SUBNET_COUNT = 6
