"""MEV builder types.

Reference parity: ethereum-consensus/src/builder/mod.rs:9-30 —
ValidatorRegistration, SignedValidatorRegistration, compute_builder_domain
(DOMAIN_APPLICATION_BUILDER with genesis fork version and zeroed
genesis_validators_root).
"""

from __future__ import annotations

from .domains import DomainType
from .models.phase0.helpers import compute_domain
from .primitives import BlsPublicKey, BlsSignature, ExecutionAddress
from .ssz import Container, uint64

__all__ = [
    "ValidatorRegistration",
    "SignedValidatorRegistration",
    "compute_builder_domain",
]


class ValidatorRegistration(Container):
    fee_recipient: ExecutionAddress
    gas_limit: uint64
    timestamp: uint64
    public_key: BlsPublicKey


class SignedValidatorRegistration(Container):
    message: ValidatorRegistration
    signature: BlsSignature


def compute_builder_domain(context) -> bytes:
    """(builder/mod.rs:26)"""
    return compute_domain(DomainType.APPLICATION_BUILDER, None, None, context)
