"""Execution-engine interface (the consensus↔execution boundary).

Reference parity: ethereum-consensus/src/execution_engine.rs:9-27 —
`PayloadRequest` marker, `ExecutionEngine` with
``verify_and_notify_new_payload``, and the `bool` mock (True accepts every
payload, False rejects). ``Context.execution_engine`` carries the mock
toggle exactly like the reference's `Context` field.
"""

from __future__ import annotations

from typing import Protocol, runtime_checkable

from .error import ExecutionEngineError

__all__ = ["PayloadRequest", "ExecutionEngine", "verify_and_notify_new_payload"]


@runtime_checkable
class PayloadRequest(Protocol):
    """Marker for data sent to the execution engine (an ExecutionPayload or
    a fork-specific NewPayloadRequest)."""


@runtime_checkable
class ExecutionEngine(Protocol):
    def verify_and_notify_new_payload(self, new_payload_request) -> None:
        """Raise ExecutionEngineError if the payload is invalid."""


def verify_and_notify_new_payload(engine, new_payload_request) -> None:
    """Dispatch that admits the reference's ``bool`` mock alongside real
    engines (execution_engine.rs:21-27)."""
    if isinstance(engine, bool):
        if not engine:
            raise ExecutionEngineError("execution engine rejected payload")
        return
    engine.verify_and_notify_new_payload(new_payload_request)
