"""Columnar operation-pool store (docs/POOL.md).

The write data plane's hot state: every attestation aggregate the
admission engine accepts lands here as ONE ROW of a packed uint64
bitfield matrix keyed by ``(slot, committee_key, data_root)`` — the
``AggregateGroup``. Redundancy elimination (exact duplicates, subsets of
an already-held aggregate) is a vectorized mask over the group's matrix,
so the common gossip case — aggregators re-publishing near-identical
views of the same committee — is rejected for the cost of a few word-ops
before any cryptography runs. Best-aggregate selection for block
production walks the same matrices (``pool/selection.py``).

The scalar twin of every bitfield operation lives right next to the
vectorized one (python ints as bitmasks, ``scalar=True``) — the live
fallback when numpy is absent AND the differential oracle
``tests/test_pool.py`` diffs against, the ``ops_vector`` house pattern.

Beyond attestations the pool holds the block-includable singleton ops —
voluntary exits, proposer slashings, attester slashings, BLS-to-execution
changes — deduplicated by their natural key, plus the equivocation
ledger: one vote record per ``(validator, target_epoch)``; a verified
attestation contradicting a recorded vote surfaces an
``AttesterSlashing`` into the pool (``pool.slashings_surfaced``), which
block production then executes through ``process_attester_slashing``.

Concurrency (speclint scope): every read and write of pool state holds
``OperationPool._lock``; the lock is never held while calling into a
snapshot or the bls layer, so it can never participate in a lock-order
cycle with ``Snapshot._lock`` or the metric locks.
"""

from __future__ import annotations

import threading
import weakref

from ..telemetry import metrics as _metrics

__all__ = ["AggregateGroup", "OperationPool", "pack_bits", "bits_to_int",
           "registered_pools"]

# every live OperationPool, for the memory observatory's ``pool.store``
# owner census (telemetry/memory.py): bitfield-matrix bytes + held rows
# across the process.
_POOLS: "weakref.WeakValueDictionary" = weakref.WeakValueDictionary()


def registered_pools() -> list:
    """Live OperationPool instances (census snapshot, GC-safe)."""
    return [p for p in (r() for r in _POOLS.valuerefs()) if p is not None]

# one uint64 lane holds 64 committee members; mainnet committees are
# ~64-2048 members → 1-32 words per row
_WORD = 64


def _np():
    try:
        import numpy

        return numpy
    except Exception:  # noqa: BLE001 — environment without numpy
        return None


def pack_bits(bits) -> "object":
    """Bool sequence → little-endian packed uint64 row (numpy)."""
    np = _np()
    arr = np.asarray(bits, dtype=np.uint8)
    n_words = (arr.shape[0] + _WORD - 1) // _WORD
    packed = np.packbits(arr, bitorder="little")
    out = np.zeros(n_words * 8, dtype=np.uint8)
    out[: packed.shape[0]] = packed
    return out.view("<u8")


def bits_to_int(bits) -> int:
    """Bool sequence → python int bitmask (the scalar twin's row)."""
    mask = 0
    for i, b in enumerate(bits):
        if b:
            mask |= 1 << i
    return mask


class AggregateGroup:
    """Every aggregate held for one ``(slot, committee_key, data_root)``.

    ``bits`` is the packed matrix (rows = aggregates, columns = packed
    committee positions); ``masks`` is the scalar twin (one python int
    per row) maintained in lockstep so the vectorized and scalar engines
    answer dedup/selection questions identically. Rows are append-only;
    the matrix grows by doubling, and readers always slice ``[:n]``.
    Access is guarded by the owning pool's lock."""

    __slots__ = (
        "slot",
        "committee_key",
        "data_root",
        "committee_size",
        "bits",
        "masks",
        "n",
        "signatures",
        "attestations",
    )

    def __init__(self, slot: int, committee_key, data_root: bytes,
                 committee_size: int):
        self.slot = int(slot)
        self.committee_key = committee_key
        self.data_root = bytes(data_root)
        self.committee_size = int(committee_size)
        self.bits = None  # lazily shaped on first insert
        self.masks: list = []  # scalar-twin rows (python ints)
        self.n = 0
        self.signatures: list = []  # compressed signature bytes per row
        self.attestations: list = []  # the SSZ containers, row-aligned

    # -- dedup ---------------------------------------------------------------
    def classify(self, bit_list, scalar: bool = False) -> str:
        """``new`` / ``duplicate`` / ``subset`` of an incoming aggregate
        against the held rows. A duplicate is an exact row match; a
        subset adds no attester any held row doesn't already cover."""
        mask = bits_to_int(bit_list)
        if scalar or self.bits is None or _np() is None:
            for held in self.masks[: self.n]:
                if held == mask:
                    return "duplicate"
            for held in self.masks[: self.n]:
                if mask & ~held == 0:
                    return "subset"
            return "new"
        np = _np()
        row = pack_bits(bit_list)
        held = self.bits[: self.n]
        if bool(np.any(np.all(held == row, axis=1))):
            return "duplicate"
        if bool(np.any(np.all(row & ~held == 0, axis=1))):
            return "subset"
        return "new"

    def insert(self, bit_list, signature: bytes, attestation) -> int:
        """Append one aggregate row (caller already classified it as
        ``new``); returns the row index."""
        np = _np()
        mask = bits_to_int(bit_list)
        if np is not None:
            row = pack_bits(bit_list)
            if self.bits is None:
                self.bits = np.zeros((4, row.shape[0]), dtype=np.uint64)
            elif self.n == self.bits.shape[0]:
                grown = np.zeros(
                    (self.bits.shape[0] * 2, self.bits.shape[1]),
                    dtype=np.uint64,
                )
                grown[: self.n] = self.bits[: self.n]
                self.bits = grown
            self.bits[self.n] = row
        self.masks.append(mask)
        self.signatures.append(bytes(signature))
        self.attestations.append(attestation)
        self.n += 1
        return self.n - 1

    def coverage_mask(self) -> int:
        """Union of every held row (scalar form)."""
        covered = 0
        for mask in self.masks[: self.n]:
            covered |= mask
        return covered

    def __len__(self) -> int:
        return self.n

    def __repr__(self) -> str:
        return (
            f"AggregateGroup(slot={self.slot}, key={self.committee_key!r}, "
            f"root=0x{self.data_root.hex()[:8]}…, rows={self.n})"
        )


class _VoteRecord:
    """One verified attester vote per (validator, target_epoch): enough
    of the indexed attestation to rebuild it for a slashing. The source
    epoch is denormalized out of ``data`` so the surround scan reads an
    int per record instead of walking SSZ containers."""

    __slots__ = ("data_root", "indices", "data", "signature",
                 "source_epoch")

    def __init__(self, data_root: bytes, indices, data, signature: bytes):
        self.data_root = bytes(data_root)
        self.indices = tuple(int(i) for i in indices)
        self.data = data
        self.signature = bytes(signature)
        self.source_epoch = int(data.source.epoch)


class OperationPool:
    """The write data plane's operation state: attestation aggregate
    groups plus the singleton op pools, all behind one lock."""

    def __init__(self, max_groups: int = 4096, max_votes: int = 1 << 16):
        self._lock = threading.Lock()
        self._groups: dict = {}  # (slot, committee_key, data_root) -> group
        self._exits: dict = {}  # validator index -> SignedVoluntaryExit
        self._proposer_slashings: dict = {}  # proposer index -> slashing
        self._attester_slashings: dict = {}  # htr root -> container
        self._bls_changes: dict = {}  # validator index -> signed change
        self._votes: dict = {}  # (validator, target_epoch) -> _VoteRecord
        self._max_groups = int(max_groups)
        self._max_votes = int(max_votes)
        self._seq = 0
        _POOLS[id(self)] = self  # memory-observatory census membership

    # -- attestations --------------------------------------------------------
    def classify_attestation(self, key, committee_size: int, bit_list,
                             scalar: bool = False) -> str:
        """Dedup verdict for an incoming aggregate without inserting —
        the admission engine's pre-crypto redundancy gate."""
        with self._lock:
            group = self._groups.get(key)
            if group is None:
                return "new"
            return group.classify(bit_list, scalar=scalar)

    def insert_attestation(self, key, committee_size: int, bit_list,
                           signature: bytes, attestation,
                           scalar: bool = False) -> "tuple[int | None, str]":
        """Insert a VERIFIED aggregate; returns ``(row index, "new")``
        on insertion, or ``(None, "duplicate"|"subset")`` — the insert
        re-classifies under the pool lock, so it doubles as the settle
        path's in-order redundancy verdict (one vector pass, no
        classify-then-insert double walk)."""
        slot, committee_key, data_root = key
        with self._lock:
            group = self._groups.get(key)
            if group is None:
                if len(self._groups) >= self._max_groups:
                    oldest = min(self._groups, key=lambda k: k[0])
                    del self._groups[oldest]
                    _metrics.counter("pool.groups.evicted").inc()
                group = AggregateGroup(slot, committee_key, data_root,
                                       committee_size)
                self._groups[key] = group
            verdict = group.classify(bit_list, scalar=scalar)
            if verdict != "new":
                return None, verdict
            row = group.insert(bit_list, signature, attestation)
            self._seq += 1
        _metrics.counter("pool.attestations.held").inc()
        _metrics.gauge("pool.groups").set(len(self._groups))
        return row, "new"

    def groups(self, slot=None, committee_index=None) -> list:
        """Consistent list of groups (sorted by key — the canonical
        selection / serving order), optionally filtered the Beacon-API
        way (``?slot=`` / ``?committee_index=``)."""
        with self._lock:
            out = [
                self._groups[k]
                for k in sorted(self._groups, key=_group_sort_key)
            ]
        if slot is not None:
            out = [g for g in out if g.slot == int(slot)]
        if committee_index is not None:
            wanted = int(committee_index)
            out = [
                g for g in out
                if (g.committee_key == wanted
                    or (isinstance(g.committee_key, tuple)
                        and wanted in g.committee_key))
            ]
        return out

    def attestations_view(self, slot=None, committee_index=None) -> list:
        """Every held aggregate as its SSZ container, group-sorted then
        row-ordered — the ``GET /eth/v1/beacon/pool/attestations`` body,
        identical between the vectorized and scalar engines because
        insertion order is admission order in both."""
        out = []
        for group in self.groups(slot=slot, committee_index=committee_index):
            with self._lock:
                out.extend(group.attestations[: group.n])
        return out

    # -- the equivocation ledger --------------------------------------------
    def note_votes(self, attesting_indices, data, data_root: bytes,
                   signature: bytes, builder) -> list:
        """Record one verified attestation's votes; returns any
        ``AttesterSlashing`` containers surfaced by a contradiction —
        BOTH arms of ``is_slashable_attestation_data``:

        * **double vote** — same validator, same target epoch, different
          data (the ledger's primary key collides);
        * **surround vote** — the same validator's vote in ANOTHER
          target epoch where one vote's (source, target) span strictly
          contains the other's (``source_1 < source_2`` and
          ``target_2 < target_1``). The scan walks the validator's
          records across the ledger's target-epoch maps — O(live
          epochs) int compares per attester, and the spec's surround
          arm needs exactly the cross-epoch records the ledger already
          keeps (docs/POOL.md).

        ``builder`` is the fork namespace used to rebuild the two
        ``IndexedAttestation`` halves; ``attestation_1`` is always the
        half the spec predicate orders first (the earlier double vote /
        the SURROUNDING vote). Slashings land in the pool's own
        attester-slashing pool as well as being returned."""
        data_root = bytes(data_root)
        target_epoch = int(data.target.epoch)
        record = _VoteRecord(data_root, sorted(attesting_indices), data,
                             signature)
        surfaced = []
        with self._lock:
            epoch_votes = self._votes.get(target_epoch)
            if epoch_votes is None:
                epoch_votes = self._votes[target_epoch] = {}
            if len(epoch_votes) >= self._max_votes:
                epoch_votes.clear()  # bounded ledger, epoch-scoped
            pairs = []  # (surrounding-or-earlier, other) in spec order
            for index in record.indices:
                prior = epoch_votes.setdefault(index, record)
                if prior is not record and prior.data_root != data_root:
                    pairs.append((prior, record))
                for other_epoch, other_votes in self._votes.items():
                    if other_epoch == target_epoch:
                        continue
                    other = other_votes.get(index)
                    if other is None:
                        continue
                    if (other.source_epoch < record.source_epoch
                            and target_epoch < other_epoch):
                        # the OTHER vote surrounds the new one
                        pairs.append((other, record))
                    elif (record.source_epoch < other.source_epoch
                            and other_epoch < target_epoch):
                        # the new vote surrounds the other
                        pairs.append((record, other))
            for first, second in pairs:
                slashing = _build_slashing(first, second, builder)
                root = bytes(type(slashing).hash_tree_root(slashing))
                if root not in self._attester_slashings:
                    self._attester_slashings[root] = slashing
                    surfaced.append(slashing)
        for _ in surfaced:
            _metrics.counter("pool.slashings_surfaced").inc()
        return surfaced

    def vote_ledger_digest(self) -> "list":
        """A deterministic digest of the equivocation ledger — one
        ``(target_epoch, validator, data_root hex, source_epoch)`` row
        per recorded vote, sorted — the production soak's end-of-run
        ledger bit-identity comparand (docs/SOAK.md)."""
        with self._lock:
            return sorted(
                (epoch, index, record.data_root.hex(),
                 record.source_epoch)
                for epoch, votes in self._votes.items()
                for index, record in votes.items()
            )

    # -- singleton op pools --------------------------------------------------
    def insert_voluntary_exit(self, signed_exit) -> bool:
        index = int(signed_exit.message.validator_index)
        with self._lock:
            if index in self._exits:
                return False
            self._exits[index] = signed_exit
        _metrics.counter("pool.voluntary_exits.held").inc()
        return True

    def insert_proposer_slashing(self, slashing) -> bool:
        index = int(slashing.signed_header_1.message.proposer_index)
        with self._lock:
            if index in self._proposer_slashings:
                return False
            self._proposer_slashings[index] = slashing
        _metrics.counter("pool.proposer_slashings.held").inc()
        return True

    def insert_attester_slashing(self, slashing) -> bool:
        root = bytes(type(slashing).hash_tree_root(slashing))
        with self._lock:
            if root in self._attester_slashings:
                return False
            self._attester_slashings[root] = slashing
        _metrics.counter("pool.attester_slashings.held").inc()
        return True

    def insert_bls_change(self, signed_change) -> bool:
        index = int(signed_change.message.validator_index)
        with self._lock:
            if index in self._bls_changes:
                return False
            self._bls_changes[index] = signed_change
        _metrics.counter("pool.bls_changes.held").inc()
        return True

    def op_held(self, kind: str, container) -> bool:
        """Pre-crypto duplicate probe for a singleton op (the admission
        engine's cheap-reject gate; insertion re-checks under the same
        lock, so a racing admit is still counted as a duplicate)."""
        with self._lock:
            if kind == "voluntary_exit":
                return int(container.message.validator_index) in self._exits
            if kind == "proposer_slashing":
                return (
                    int(container.signed_header_1.message.proposer_index)
                    in self._proposer_slashings
                )
            if kind == "attester_slashing":
                root = bytes(type(container).hash_tree_root(container))
                return root in self._attester_slashings
            return (
                int(container.message.validator_index) in self._bls_changes
            )

    def voluntary_exits(self) -> list:
        with self._lock:
            return [self._exits[k] for k in sorted(self._exits)]

    def proposer_slashings(self) -> list:
        with self._lock:
            return [
                self._proposer_slashings[k]
                for k in sorted(self._proposer_slashings)
            ]

    def attester_slashings(self) -> list:
        with self._lock:
            return [
                self._attester_slashings[k]
                for k in sorted(self._attester_slashings)
            ]

    def bls_changes(self) -> list:
        with self._lock:
            return [self._bls_changes[k] for k in sorted(self._bls_changes)]

    # -- lifecycle -----------------------------------------------------------
    def prune_included(self, body) -> None:
        """Drop ops a just-produced (or observed) block body carries —
        the post-production drain."""
        with self._lock:
            for att in body.attestations:
                data_root = bytes(
                    type(att.data).hash_tree_root(att.data)
                )
                for key in [
                    k for k in self._groups if k[2] == data_root
                ]:
                    del self._groups[key]
            for op in body.voluntary_exits:
                self._exits.pop(int(op.message.validator_index), None)
            for op in body.proposer_slashings:
                self._proposer_slashings.pop(
                    int(op.signed_header_1.message.proposer_index), None
                )
            for op in body.attester_slashings:
                root = bytes(type(op).hash_tree_root(op))
                self._attester_slashings.pop(root, None)
            for op in getattr(body, "bls_to_execution_changes", ()):
                self._bls_changes.pop(int(op.message.validator_index), None)
        _metrics.gauge("pool.groups").set(len(self._groups))

    def prune_expired(self, slot: int, slots_per_epoch: int) -> int:
        """Drop attestation groups past their inclusion window (and the
        vote ledger's expired epochs); returns groups dropped."""
        slot = int(slot)
        horizon_epoch = max(0, slot // int(slots_per_epoch) - 2)
        with self._lock:
            stale = [
                key for key, g in self._groups.items()
                if g.slot + int(slots_per_epoch) < slot
            ]
            for key in stale:
                del self._groups[key]
            dead_votes = [
                epoch for epoch in self._votes if epoch < horizon_epoch
            ]
            for epoch in dead_votes:
                del self._votes[epoch]
        if stale:
            _metrics.counter("pool.groups.expired").inc(len(stale))
            _metrics.gauge("pool.groups").set(len(self._groups))
        return len(stale)

    def counts(self) -> dict:
        """The ``/pool`` introspection summary."""
        with self._lock:
            return {
                "attestation_groups": len(self._groups),
                "attestation_rows": sum(
                    g.n for g in self._groups.values()
                ),
                "voluntary_exits": len(self._exits),
                "proposer_slashings": len(self._proposer_slashings),
                "attester_slashings": len(self._attester_slashings),
                "bls_to_execution_changes": len(self._bls_changes),
                "vote_records": sum(
                    len(v) for v in self._votes.values()
                ),
            }

    def memory_census(self) -> "tuple[int, int]":
        """(resident bytes, held aggregate rows) for the memory
        observatory's ``pool.store`` owner: the packed bitfield
        matrices (full allocated capacity, not just the ``[:n]`` live
        slice — doubling growth retains the whole buffer), the
        signature bytes, and the vote ledger's fixed-size records
        (pointer-width estimate per record)."""
        with self._lock:
            nbytes = 0
            rows = 0
            for group in self._groups.values():
                rows += group.n
                bits = group.bits
                if bits is not None:
                    nbytes += int(bits.nbytes)
                nbytes += sum(len(s) for s in group.signatures)
                nbytes += len(group.masks) * 8
            nbytes += sum(len(v) for v in self._votes.values()) * 128
            return nbytes, rows

    def clear(self) -> None:
        with self._lock:
            self._groups = {}
            self._exits = {}
            self._proposer_slashings = {}
            self._attester_slashings = {}
            self._bls_changes = {}
            self._votes = {}

    def __repr__(self) -> str:
        c = self.counts()
        return (
            f"OperationPool({c['attestation_groups']} groups / "
            f"{c['attestation_rows']} aggregates, "
            f"{c['voluntary_exits']} exits, "
            f"{c['attester_slashings']} att-slashings)"
        )


def _build_slashing(first, second, builder):
    """One ``AttesterSlashing`` from two vote records already ordered
    for ``is_slashable_attestation_data`` (attestation_1 = the earlier
    double vote / the surrounding vote)."""
    return builder.AttesterSlashing(
        attestation_1=builder.IndexedAttestation(
            attesting_indices=list(first.indices),
            data=first.data.copy(),
            signature=first.signature,
        ),
        attestation_2=builder.IndexedAttestation(
            attesting_indices=list(second.indices),
            data=second.data.copy(),
            signature=second.signature,
        ),
    )


def _group_sort_key(key):
    """Canonical group order shared by serving and selection: slot, then
    committee key (ints before tuples, both orderable), then data root."""
    slot, committee_key, data_root = key
    if isinstance(committee_key, tuple):
        ck = (1,) + committee_key
    else:
        ck = (0, int(committee_key))
    return (slot, ck, data_root)
