"""Block production: drain the pool against a HeadStore snapshot
(docs/POOL.md).

``produce_block`` builds a valid block for ``snapshot.slot + 1`` (or a
requested slot) whose body is packed from the pool: the vectorized
best-aggregate selection's attestations, plus every still-valid exit,
slashing, and BLS-to-execution change up to the fork's per-block caps.
Candidate ops are TRIAL-EXECUTED in block operation order on one scratch
copy of the advanced state (signature checks deferred — they were proven
at admission), so an op invalidated since admission (an exit for a
meanwhile-slashed validator, a slashing already applied on chain) is
dropped instead of poisoning the block. The final body then replays
through the fork's own ``process_block`` — every signature, including
the pool's aggregates, re-proves in one RLC flush — before the state
root is stamped, so a produced block is valid by construction and
replays bit-identically through the scalar oracle (the acceptance
``tests/test_pool.py`` asserts).

Key material never lives here: ``randao`` and ``sign`` are callbacks
with the shapes of ``tests/chain_utils.make_randao_reveal`` /
``sign_block`` (the scenario-mutator convention). Without ``sign`` the
signed envelope carries an empty signature — view-only production.
Execution-payload forks (bellatrix+) take ``body_extras(state, slot,
context) -> dict`` to supply the payload (and any other body fields);
phase0/altair production is self-contained — an empty sync aggregate is
the G2 infinity point per the no-participants rule.
"""

from __future__ import annotations

import time

from ..error import Error
from ..models.signature_batch import collect_signatures
from ..telemetry import metrics as _metrics
from ..utils import trace
from .selection import select_aggregates

__all__ = ["produce_block", "ProductionError", "eligible_groups"]

_G2_INFINITY = b"\xc0" + b"\x00" * 95


class ProductionError(Error):
    """Block production could not assemble a valid block."""


def _fork_module(fork: str):
    import importlib

    return importlib.import_module(f"ethereum_consensus_tpu.models.{fork}")


def eligible_groups(pool, state, slot: int, context, fork: str) -> list:
    """The pool's aggregate groups includable at ``slot`` on ``state``:
    inside the inclusion window, targeting the right epoch, sourcing the
    state's matching justified checkpoint — the non-crypto half of the
    fork's attestation validation, applied group-wise (every row of a
    group shares its data)."""
    spe = int(context.SLOTS_PER_EPOCH)
    current_epoch = int(state.slot) // spe
    previous_epoch = max(0, current_epoch - 1)
    electra = fork == "electra"
    out = []
    for group in pool.groups():
        if group.slot + int(context.MIN_ATTESTATION_INCLUSION_DELAY) > slot:
            continue
        if not electra and group.slot + spe < slot:
            continue
        data = group.attestations[0].data
        target_epoch = int(data.target.epoch)
        if target_epoch not in (previous_epoch, current_epoch):
            continue
        source = (
            state.current_justified_checkpoint
            if target_epoch == current_epoch
            else state.previous_justified_checkpoint
        )
        if data.source != source:
            continue
        out.append(group)
    return out


def _trial(fn, scratch, op, context) -> bool:
    """Structurally apply one candidate op on the production scratch
    (signatures collected, not verified — admission proved them);
    False drops the candidate."""
    try:
        with collect_signatures():
            fn(scratch, op, context)
        return True
    except Error:
        _metrics.counter("pool.production.dropped").inc()
        return False


def produce_block(snapshot, pool, context, slot: "int | None" = None,
                  randao=None, sign=None, body_extras=None,
                  scalar_selection: bool = False):
    """Drain the pool into a signed block on top of ``snapshot``.

    Returns the fork's ``SignedBeaconBlock`` (empty signature when no
    ``sign`` callback). Raises ``ProductionError`` when the assembled
    body cannot replay cleanly — a bug or a poisoned pool, never a
    normal outcome."""
    t0 = time.perf_counter()
    fork = snapshot.fork
    mod = _fork_module(fork)
    ns = mod.build(context.preset)
    from ..models.phase0 import helpers as h
    from ..models.phase0.containers import BeaconBlockHeader

    state = snapshot.raw.copy()
    if slot is None:
        slot = int(snapshot.slot) + 1
    slot = int(slot)
    with trace.span("pool.produce", slot=slot, fork=fork):
        if int(state.slot) < slot:
            mod.slot_processing.process_slots(state, slot, context)
        proposer_index = h.get_beacon_proposer_index(state, context)
        bp = mod.block_processing

        # trial-execute candidates in block operation order on ONE
        # scratch: later ops see earlier ops' effects exactly as the
        # real block application will
        v_scratch = state.copy()
        electra = fork == "electra"
        max_ps = int(context.MAX_PROPOSER_SLASHINGS)
        max_as = int(
            getattr(context, "MAX_ATTESTER_SLASHINGS_ELECTRA",
                    context.MAX_ATTESTER_SLASHINGS)
            if electra
            else context.MAX_ATTESTER_SLASHINGS
        )
        max_att = int(
            getattr(context, "MAX_ATTESTATIONS_ELECTRA",
                    context.MAX_ATTESTATIONS)
            if electra
            else context.MAX_ATTESTATIONS
        )
        max_exits = int(context.MAX_VOLUNTARY_EXITS)

        proposer_slashings = [
            op.copy() for op in pool.proposer_slashings()
            if _trial(bp.process_proposer_slashing, v_scratch, op, context)
        ][:max_ps]
        attester_slashings = [
            op.copy() for op in pool.attester_slashings()
            if _trial(bp.process_attester_slashing, v_scratch, op, context)
        ][:max_as]

        groups = eligible_groups(pool, state, slot, context, fork)
        picks = select_aggregates(groups, max_att, scalar=scalar_selection)
        attestations = []
        for group, row in picks:
            att = group.attestations[row].copy()
            if _trial(bp.process_attestation, v_scratch, att, context):
                attestations.append(att)

        voluntary_exits = [
            op.copy() for op in pool.voluntary_exits()
            if _trial(bp.process_voluntary_exit, v_scratch, op, context)
        ][:max_exits]

        body_kwargs = dict(
            randao_reveal=(
                randao(state, slot, context) if randao is not None
                else b"\x00" * 96
            ),
            eth1_data=state.eth1_data.copy(),
            proposer_slashings=proposer_slashings,
            attester_slashings=attester_slashings,
            attestations=attestations,
            voluntary_exits=voluntary_exits,
        )
        if fork != "phase0":
            body_kwargs["sync_aggregate"] = ns.SyncAggregate(
                sync_committee_bits=[False]
                * int(context.SYNC_COMMITTEE_SIZE),
                sync_committee_signature=_G2_INFINITY,
            )
        if "bls_to_execution_changes" in getattr(
            ns.BeaconBlockBody, "__ssz_fields__", {}
        ):
            changes = [
                op.copy() for op in pool.bls_changes()
                if _trial(
                    bp.process_bls_to_execution_change, v_scratch, op,
                    context,
                )
            ][: int(context.MAX_BLS_TO_EXECUTION_CHANGES)]
            body_kwargs["bls_to_execution_changes"] = changes
        if body_extras is not None:
            body_kwargs.update(body_extras(state, slot, context))
        body = ns.BeaconBlockBody(**body_kwargs)

        block = ns.BeaconBlock(
            slot=slot,
            proposer_index=proposer_index,
            parent_root=BeaconBlockHeader.hash_tree_root(
                state.latest_block_header
            ),
            body=body,
        )
        # the validity proof: the assembled body replays through the
        # fork's own process_block — every collected signature (randao,
        # pool aggregates, ops) proves in one RLC flush — before the
        # state root is stamped
        scratch = state.copy()
        try:
            if randao is None:
                with collect_signatures():
                    bp.process_block(scratch, block, context)
            else:
                with collect_signatures() as batch:
                    bp.process_block(scratch, block, context)
                batch.flush()
        except Error as exc:
            raise ProductionError(
                f"assembled block failed replay: {type(exc).__name__}: {exc}"
            ) from exc
        block.state_root = type(scratch).hash_tree_root(scratch)

        if sign is not None:
            signature = sign(state, block, context)
        else:
            signature = b"\x00" * 96
        signed = ns.SignedBeaconBlock(message=block, signature=signature)
    _metrics.counter("pool.blocks_produced").inc()
    _metrics.histogram("pool.produce_s").observe(time.perf_counter() - t0)
    return signed
