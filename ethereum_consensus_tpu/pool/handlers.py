"""Pool data plane: the Beacon-API WRITE surface (docs/POOL.md).

``PoolDataPlane`` mounts beside the PR 8 read plane on the introspection
server (longest-prefix app routing, ``telemetry/server.py``) and owns:

* ``POST /eth/v1/beacon/pool/attestations`` — batch admission through
  the ``AdmissionEngine``: the whole request admits, the partial window
  flushes, and every ticket settles before the response, so rejections
  come back in the standard per-index failure envelope.
* ``POST /eth/v1/beacon/pool/{voluntary_exits,attester_slashings,
  proposer_slashings,bls_to_execution_changes}`` — singleton-op
  admission, same settle-before-respond contract.
* the matching ``GET`` pool views — held ops in canonical order, wire
  format chosen so ``api/client.py`` round-trips them bit-identically
  to the scalar-twin pool.
* ``POST /eth/v2/beacon/blocks`` (and v1) — block publication into the
  chain pipeline via the injected ``submit`` callable; a rejected block
  surfaces its structured error in the 400 body.
* ``GET /pool`` — introspection: held-op counts, admission window
  state, rejection counters by reason.

JSON decode errors never raise out: an undecodable item is a
``malformed`` rejection like any other, carried per index.
"""

from __future__ import annotations

from ..telemetry import metrics as _metrics
from .admission import REASONS, _note_rejection

__all__ = ["PoolDataPlane"]


class PoolDataPlane:
    """Mountable write plane over an ``AdmissionEngine`` (which owns the
    pool + head store). ``submit``, when given, receives decoded
    ``SignedBeaconBlock`` containers from block publication."""

    prefix = "/eth/v1/beacon/pool/"
    prefixes = (
        "/eth/v1/beacon/pool/",
        "/eth/v1/beacon/blocks",
        "/eth/v2/beacon/blocks",
        "/pool",
    )

    ROUTES = (
        "GET  /eth/v1/beacon/pool/attestations?slot=&committee_index=",
        "POST /eth/v1/beacon/pool/attestations",
        "GET  /eth/v1/beacon/pool/voluntary_exits",
        "POST /eth/v1/beacon/pool/voluntary_exits",
        "GET  /eth/v1/beacon/pool/attester_slashings",
        "POST /eth/v1/beacon/pool/attester_slashings",
        "GET  /eth/v1/beacon/pool/proposer_slashings",
        "POST /eth/v1/beacon/pool/proposer_slashings",
        "GET  /eth/v1/beacon/pool/bls_to_execution_changes",
        "POST /eth/v1/beacon/pool/bls_to_execution_changes",
        "POST /eth/v1/beacon/blocks",
        "POST /eth/v2/beacon/blocks",
        "GET  /pool",
    )

    def __init__(self, engine, submit=None):
        self.engine = engine
        self.submit = submit

    # -- plumbing ------------------------------------------------------------
    @property
    def pool(self):
        return self.engine.pool

    def _param(self, params: dict, key: str):
        values = params.get(key)
        return values[0] if values else None

    def _ns(self):
        """The head fork's container namespace (the wire types)."""
        snap = self.engine.store.head
        if snap is None:
            return None
        return self.engine._builder(snap.fork)

    def handle(self, method: str, path: str, params: dict, body):
        """(status, document); never raises — server contract."""
        try:
            return self._dispatch(method, path, params, body)
        except Exception as exc:  # noqa: BLE001 — a client must get a reply
            _metrics.counter("pool.handler_errors").inc()
            return 500, {"code": 500,
                         "message": f"{type(exc).__name__}: {exc}"}

    def _dispatch(self, method: str, path: str, params: dict, body):
        if path == "/pool" and method == "GET":
            return self._introspect()
        if path in ("/eth/v1/beacon/blocks", "/eth/v2/beacon/blocks"):
            if method != "POST":
                return 404, {"code": 404,
                             "message": f"no pool route {method} {path}"}
            return self._publish_block(body)
        leaf = path[len(self.prefix):] if path.startswith(self.prefix) else None
        handlers = {
            "attestations": (self._get_attestations,
                             self._post_attestations),
            "voluntary_exits": (
                lambda p: self._get_ops(self.pool.voluntary_exits),
                lambda b: self._post_ops(
                    b, "VoluntaryExit", self.engine.admit_voluntary_exit,
                    signed=True,
                ),
            ),
            "attester_slashings": (
                lambda p: self._get_ops(self.pool.attester_slashings),
                lambda b: self._post_ops(
                    b, "AttesterSlashing",
                    self.engine.admit_attester_slashing,
                ),
            ),
            "proposer_slashings": (
                lambda p: self._get_ops(self.pool.proposer_slashings),
                lambda b: self._post_ops(
                    b, "ProposerSlashing",
                    self.engine.admit_proposer_slashing,
                ),
            ),
            "bls_to_execution_changes": (
                lambda p: self._get_ops(self.pool.bls_changes),
                lambda b: self._post_ops(
                    b, "SignedBlsToExecutionChange",
                    self.engine.admit_bls_change,
                ),
            ),
        }
        if leaf in handlers:
            get_fn, post_fn = handlers[leaf]
            if method == "GET":
                return get_fn(params)
            if method == "POST":
                return post_fn(body)
        return 404, {"code": 404, "message": f"no pool route {method} {path}"}

    # -- attestations --------------------------------------------------------
    def _get_attestations(self, params: dict):
        slot = self._param(params, "slot")
        index = self._param(params, "committee_index")
        atts = self.pool.attestations_view(
            slot=None if slot is None else int(slot),
            committee_index=None if index is None else int(index),
        )
        return 200, {
            "data": [type(a).to_json(a) for a in atts],
        }

    def _post_attestations(self, body):
        if not isinstance(body, list):
            return 400, {"code": 400,
                         "message": "expected a JSON list of attestations"}
        ns = self._ns()
        tickets: list = []
        decoded: list = []
        for i, doc in enumerate(body):
            if ns is None:
                tickets.append((i, None, "no_head"))
                _note_rejection("no_head")
                continue
            try:
                att = ns.Attestation.from_json(doc)
            except Exception:  # noqa: BLE001 — malformed SSZ JSON
                tickets.append((i, None, "malformed"))
                _note_rejection("malformed")
                continue
            decoded.append((i, att))
        # the whole request admits as ONE batch — one admission span,
        # one window fill, at most one flush dispatch per filled window
        for (i, _att), ticket in zip(
            decoded,
            self.engine.admit_attestation_batch(
                [att for _, att in decoded]
            ),
        ):
            tickets.append((i, ticket, None))
        self.engine.settle()
        tickets.sort(key=lambda t: t[0])
        return self._admission_response(tickets)

    # -- singleton ops -------------------------------------------------------
    def _get_ops(self, reader):
        ops = reader()
        return 200, {"data": [type(op).to_json(op) for op in ops]}

    def _post_ops(self, body, type_name: str, admit, signed: bool = False):
        """Admit one op (or a list — the BLS-changes shape); settle;
        respond. ``type_name`` resolves on the head fork's namespace,
        with the ``Signed`` wrapper applied when the wire type is the
        signed envelope."""
        ns = self._ns()
        if ns is None:
            _note_rejection("no_head")
            return 503, {"code": 503, "message": "no head to validate against"}
        wire_name = f"Signed{type_name}" if signed else type_name
        wire_type = getattr(ns, wire_name, None)
        if wire_type is None:
            return 400, {
                "code": 400,
                "message": f"{wire_name} is not a {self._head_fork()} type",
            }
        docs = body if isinstance(body, list) else [body]
        tickets = []
        for i, doc in enumerate(docs):
            try:
                op = wire_type.from_json(doc)
            except Exception:  # noqa: BLE001 — malformed SSZ JSON
                tickets.append((i, None, "malformed"))
                _note_rejection("malformed")
                continue
            tickets.append((i, admit(op), None))
        self.engine.settle()
        return self._admission_response(tickets)

    def _head_fork(self):
        snap = self.engine.store.head
        return snap.fork if snap is not None else "unknown"

    def _admission_response(self, tickets):
        failures = []
        for index, ticket, early_reason in tickets:
            reason = early_reason
            if ticket is not None and ticket.status == "rejected":
                reason = ticket.reason
            if reason is not None:
                failures.append({"index": str(index), "message": reason})
        admitted = len(tickets) - len(failures)
        if failures:
            return 400, {
                "code": 400,
                "message": "one or more messages failed admission",
                "failures": failures,
                "data": {"admitted": str(admitted)},
            }
        return 200, {"data": {"admitted": str(admitted)}}

    # -- block publication ---------------------------------------------------
    def _publish_block(self, body):
        if self.submit is None:
            return 501, {"code": 501,
                         "message": "no block submission sink mounted"}
        if not isinstance(body, dict):
            return 400, {"code": 400,
                         "message": "expected a signed block document"}
        snap = self.engine.store.head
        forks = []
        if snap is not None:
            forks.append(snap.fork)
        forks.extend(
            f for f in ("electra", "deneb", "capella", "bellatrix",
                        "altair", "phase0")
            if f not in forks
        )
        block = None
        for fork in forks:
            ns = self.engine._builder(fork)
            try:
                block = ns.SignedBeaconBlock.from_json(body)
                break
            except Exception:  # noqa: BLE001 — try the next fork's shape
                continue
        if block is None:
            _note_rejection("malformed")
            return 400, {"code": 400,
                         "message": "block does not decode under any fork"}
        from ..error import Error

        try:
            self.submit(block)
        except Error as exc:
            _metrics.counter("pool.blocks_rejected").inc()
            return 400, {
                "code": 400,
                "message": f"{type(exc).__name__}: {exc}",
            }
        _metrics.counter("pool.blocks_published").inc()
        return 200, {"data": {
            "slot": str(int(block.message.slot)),
        }}

    # -- introspection -------------------------------------------------------
    def _introspect(self):
        rejected = {}
        for reason in REASONS:
            value = _metrics.counter(f"pool.rejected.{reason}").value()
            if value:
                rejected[reason] = value
        counts = self.pool.counts()
        doc = {
            "counts": counts,
            "admission": self.engine.snapshot(),
            "rejected": rejected,
            "flushes": _metrics.counter("pool.flushes").value(),
            "fused_groups": _metrics.counter("pool.fused_groups").value(),
            "blocks_produced": _metrics.counter(
                "pool.blocks_produced"
            ).value(),
            "blocks_published": _metrics.counter(
                "pool.blocks_published"
            ).value(),
        }
        return 200, doc
