"""Vectorized best-aggregate selection (docs/POOL.md).

Block production wants the most-profitable set of at most
``MAX_ATTESTATIONS`` aggregates: profit = attesters newly covered. The
canonical algorithm is a GLOBAL GREEDY over the pool's groups:

1. every group keeps a running ``covered`` union of its already-picked
   rows;
2. each step computes, per group, the best marginal gain any unpicked
   row offers over that union — ``popcount(row & ~covered)``, one
   vectorized pass over the group's packed uint64 matrix;
3. the globally best (gain, group-order, row-order) candidate is picked,
   its bits fold into the group's union, and the step repeats until the
   cap is reached or no row adds a single new attester.

Ties break deterministically — larger gain first, then the canonical
group sort order (``store._group_sort_key``), then lowest row index —
so the scalar twin (`python ints as bitmasks`, same loop) produces the
IDENTICAL pick sequence: ``tests/test_pool.py`` diffs them under
randomized traffic, and ``bench.py pool_ingest`` gates on the identity.

Subset rows (admission already rejects them) would never be picked —
their marginal gain over the superset's union is zero — so selection is
naturally "non-overlapping": every pick strictly grows coverage.
"""

from __future__ import annotations

import time

from ..telemetry import metrics as _metrics
from ..utils import trace
from .store import _np

__all__ = ["select_aggregates", "popcount_rows"]


def popcount_rows(matrix) -> "object":
    """Per-row popcount of a packed uint64 matrix (numpy). Uses the
    vectorized ``bitwise_count`` when this numpy has it (>=2.0), else an
    unpackbits pass over the byte view."""
    np = _np()
    if hasattr(np, "bitwise_count"):
        return np.bitwise_count(matrix).sum(axis=1, dtype=np.int64)
    as_bytes = matrix.view(np.uint8)
    return np.unpackbits(as_bytes, axis=1).sum(axis=1, dtype=np.int64)


def _best_row_vectorized(group, covered_row, picked) -> "tuple[int, int]":
    """(gain, row index) of the best unpicked row against the group's
    covered union — one vectorized pass."""
    np = _np()
    held = group.bits[: group.n]
    gains = popcount_rows(held & ~covered_row)
    if picked:
        gains[np.fromiter(picked, dtype=np.int64, count=len(picked))] = -1
    row = int(np.argmax(gains))  # argmax takes the FIRST max: lowest row
    return int(gains[row]), row


def _best_row_scalar(group, covered_mask: int, picked) -> "tuple[int, int]":
    best_gain, best_row = -1, -1
    for row in range(group.n):
        if row in picked:
            continue
        gain = bin(group.masks[row] & ~covered_mask).count("1")
        if gain > best_gain:
            best_gain, best_row = gain, row
    return best_gain, best_row


def select_aggregates(groups, max_count: int, scalar: bool = False) -> list:
    """Greedy-pack up to ``max_count`` aggregates from ``groups`` (the
    pool's canonical group order); returns ``[(group, row_index), ...]``
    in pick order. ``scalar=True`` runs the brute-force twin."""
    t0 = time.perf_counter()
    np = _np()
    vectorized = not scalar and np is not None
    state = []  # per group: (covered union, picked row set)
    for group in groups:
        if vectorized and group.bits is None:
            vectorized = False  # a numpy-less insert degraded this pool
    for group in groups:
        if vectorized:
            state.append([np.zeros(group.bits.shape[1], dtype=np.uint64),
                          set()])
        else:
            state.append([0, set()])
    picks: list = []
    with trace.span("pool.select", groups=len(groups), cap=max_count):
        while len(picks) < max_count:
            best = None  # (gain, group order, row)
            for gi, group in enumerate(groups):
                if group.n == len(state[gi][1]):
                    continue
                if vectorized:
                    gain, row = _best_row_vectorized(
                        group, state[gi][0], state[gi][1]
                    )
                else:
                    gain, row = _best_row_scalar(
                        group, state[gi][0], state[gi][1]
                    )
                if gain > 0 and (best is None or gain > best[0]):
                    best = (gain, gi, row)
            if best is None:
                break
            _, gi, row = best
            group = groups[gi]
            if vectorized:
                state[gi][0] |= group.bits[row]
            else:
                state[gi][0] |= group.masks[row]
            state[gi][1].add(row)
            picks.append((group, row))
    _metrics.counter("pool.selections").inc()
    _metrics.histogram("pool.selection_s").observe(time.perf_counter() - t0)
    return picks
