"""Operation pool + write data plane (docs/POOL.md).

The repo's third data plane: the READ surface (``serving/``) serves
committed state, the pipeline applies blocks — this package ACCEPTS
traffic. Attestations, aggregates, voluntary exits, slashings, and
BLS-to-execution changes ingest at line rate: structural validation on
arrival, signatures deferred into windowed cross-message RLC flushes
(``admission.py``), aggregates held as packed uint64 bitfield matrices
with vectorized redundancy elimination and best-aggregate selection
(``store.py`` / ``selection.py``), blocks produced by draining the pool
against a ``HeadStore`` snapshot (``production.py``), and the whole
surface mounted as Beacon-API POST/GET endpoints plus ``/pool``
introspection (``handlers.py``).

Every artifact — pool views, selected aggregates, produced blocks, and
every rejection reason — is bit-identical to the per-message scalar
twin (``AdmissionEngine(rlc=False)`` + ``select_aggregates(scalar=
True)``), the live fallback and differential oracle.
"""

from .admission import REASONS, Admission, AdmissionEngine  # noqa: F401
from .handlers import PoolDataPlane  # noqa: F401
from .production import ProductionError, produce_block  # noqa: F401
from .selection import select_aggregates  # noqa: F401
from .store import AggregateGroup, OperationPool  # noqa: F401

__all__ = [
    "Admission",
    "AdmissionEngine",
    "AggregateGroup",
    "OperationPool",
    "PoolDataPlane",
    "ProductionError",
    "REASONS",
    "produce_block",
    "select_aggregates",
]
