"""Admission engine: line-rate ingestion with windowed cross-message RLC
signature flushes (docs/POOL.md).

Every incoming message is validated STRUCTURALLY on arrival against the
current ``HeadStore`` snapshot — slot window, committee geometry,
bitfield shape, redundancy against the pool — and its signature claim is
DEFERRED into the current admission window. A full window flushes as one
fused verification through ``bls.verify_signature_sets_async`` (the
pipeline's stage-B entry, same FIFO worker), so the pairing cost per
admitted message approaches the cost of folding one more claim into the
batch instead of one pairing pair per message:

* **batched G2 membership** — per-message signature points are parsed
  WITHOUT the per-point subgroup check (``g2_decompress(check=False)``)
  and the whole window's points are membership-checked at once: one
  random-linear-combination G2 MSM plus a single checked round-trip.
  A failing combination falls back to per-signature checks and culls
  the offenders as ``malformed`` — exactly the reason the scalar twin's
  ``Signature.from_bytes`` raises at its parse.
* **per-group claim fusion** — window attestations for the same
  ``(slot, committee_key, data_root)`` share a signing root, so their
  claims fuse into ONE signature set: multiplicity counts over the
  committee (a column sum of the window's bitfields) feed one G1 MSM
  for the fused public-key side, and the signatures sum on the G2 side.
  D distinct data roots cost D+1 Miller loops, not 2·M pairings.
* **one RLC multi-pairing** per window over the fused sets plus any
  singleton-op sets (exits, slashings, BLS changes) — dispatched async;
  ``settle()`` maps verdicts back. A failed fused set SPLITS: members
  re-verify individually and only the offenders are rejected
  (``signature``) — the pipeline's rollback-blame discipline.

The **scalar twin** (``rlc=False``, or ``ECT_POOL_RLC=off``, or no
native backend) verifies each message inline at admission — per-message
key parse, per-message pairing — and is both the live fallback and the
differential oracle: pool contents, served views, and every rejection
reason are bit-identical between the engines for any admission sequence
(``tests/test_pool.py``). Caveat, documented in docs/POOL.md: claim
fusion is OPTIMISTIC — a crafted pair of individually-invalid signatures
that cancels within one group's sum passes the fused check (split never
runs); block production re-validates every selected aggregate through
the fork's own ``process_block``, so such poison cannot reach a chain.

Singleton ops are validated by the fork's OWN processors on a memoized
scratch copy of the snapshot state inside a ``collect_signatures``
scope — structural semantics cannot drift from the spec because they ARE
the spec functions; only the verification moment moves.

Rejection is never silent: every rejection bumps
``pool.rejected.{reason}`` and emits a one-shot ``pool.rejected`` trace
event per reason per process (the ``ops_vector.fallback`` pattern).

Locking: ``AdmissionEngine._lock`` guards the window, in-flight list,
caches, and ticket transitions; it is never held across snapshot memo
builds, native calls, or pool-lock acquisition's own critical sections
(pool methods take their lock internally, engine lock released first).
"""

from __future__ import annotations

import hashlib
import secrets
import threading
import time

from .. import _env
from ..crypto import bls
from ..error import Error
from ..models.signature_batch import collect_signatures
from ..telemetry import metrics as _metrics
from ..utils import trace

__all__ = ["AdmissionEngine", "Admission", "REASONS", "DEFAULT_WINDOW"]

DEFAULT_WINDOW = 64
_RLC_ENV = "ECT_POOL_RLC"  # =off forces the scalar per-message twin

# the structured rejection taxonomy — every reason is a counter
# (pool.rejected.<reason>) and a one-shot trace event; no other exit
# from admission exists, so nothing can drop silently
REASONS = (
    "no_head",            # nothing published to validate against
    "malformed",          # undecodable payload / invalid signature point
    "future_slot",        # attestation slot ahead of the head
    "expired",            # attestation past its inclusion window
    "invalid",            # structural spec violation (target, op rules)
    "unknown_committee",  # committee index out of range
    "bits_mismatch",      # aggregation bits != committee size
    "duplicate",          # exact aggregate / op already held
    "subset",             # adds no attester the pool doesn't cover
    "signature",          # the claim's signature does not verify
)

_REJECT_SEEN: set = set()
_REJECT_LOCK = threading.Lock()


def _note_rejection(reason: str) -> None:
    """Counter per occurrence, trace event once per reason per process."""
    _metrics.counter(f"pool.rejected.{reason}").inc()
    if reason not in _REJECT_SEEN:
        with _REJECT_LOCK:
            if reason not in _REJECT_SEEN:
                _REJECT_SEEN.add(reason)
                trace.event("pool.rejected", reason=reason)


def _native() -> bool:
    try:
        return bls.backend_name() == "native"
    except Exception:  # noqa: BLE001 — backend probe must not raise here
        return False


def _rlc_disabled() -> bool:
    return _env.flag_off(_RLC_ENV)


class Admission:
    """One message's admission ticket: ``pending`` until its window
    settles (RLC mode), then ``admitted`` or ``rejected`` + reason.
    Scalar-mode tickets resolve before ``admit_*`` returns."""

    __slots__ = ("kind", "status", "reason", "order",
                 "key", "bits", "indices", "committee_ref", "msg_root",
                 "sig_bytes", "sig_raw", "container", "snap", "sets",
                 "set_verdicts", "sig_ok", "trace_id")

    def __init__(self, kind: str, order: int):
        self.kind = kind
        self.status = "pending"
        self.reason = None
        self.order = order
        # the causal trace of the flush window this ticket rode
        # (stamped at dispatch; None while tracing is off or pending)
        self.trace_id = None
        self.key = None
        self.bits = None
        self.indices = None
        self.committee_ref = None  # (committee, pk_objs, raws-slot) record
        self.msg_root = None
        self.sig_bytes = None
        self.sig_raw = None
        self.container = None
        self.snap = None
        self.sets = None  # singleton ops: collected SignatureSets
        self.set_verdicts = None
        self.sig_ok = None

    def __repr__(self) -> str:
        tail = f", {self.reason}" if self.reason else ""
        return f"Admission({self.kind}, {self.status}{tail})"


class AdmissionEngine:
    """Windowed RLC admission over an ``OperationPool`` + ``HeadStore``.

    ``window_size`` counts MESSAGES per flush window; ``max_inflight``
    bounds dispatched-but-unsettled windows (backpressure: the oldest
    settles inline when exceeded — the pipeline's bounded-queue idiom).
    """

    def __init__(self, pool, store, context, window_size: int = DEFAULT_WINDOW,
                 rlc: "bool | None" = None, max_inflight: int = 2):
        self._lock = threading.Lock()
        self.pool = pool
        self.store = store
        self.context = context
        self.window_size = max(1, int(window_size))
        if rlc is None:
            rlc = _native() and not _rlc_disabled()
        elif rlc and (not _native() or _rlc_disabled()):
            _metrics.counter("pool.fallback.no_native").inc()
            rlc = False
        self.rlc = bool(rlc)
        self.max_inflight = max(1, int(max_inflight))
        self._window: list = []
        self._inflight: list = []  # (future|None, sets, attribution, entries, trace ctx)
        self._committees: dict = {}  # (root, slot, ckey) -> [committee, objs, raws|None]
        self._builders: dict = {}  # fork name -> container namespace
        self._scratches: dict = {}  # snapshot root -> mutable op scratch
        self._data_roots: dict = {}  # serialized AttestationData -> root
        self._order = 0

    # -- plumbing ------------------------------------------------------------
    def _head(self):
        return self.store.head

    def _builder(self, fork: str):
        with self._lock:
            ns = self._builders.get(fork)
        if ns is None:
            import importlib

            mod = importlib.import_module(
                f"ethereum_consensus_tpu.models.{fork}"
            )
            ns = mod.build(self.context.preset)
            with self._lock:
                self._builders[fork] = ns
        return ns

    def _fork_module(self, fork: str):
        import importlib

        return importlib.import_module(
            f"ethereum_consensus_tpu.models.{fork}"
        )

    def _reject(self, entry: Admission, reason: str) -> Admission:
        with self._lock:
            entry.status = "rejected"
            entry.reason = reason
        _note_rejection(reason)
        return entry

    def _admit(self, entry: Admission) -> Admission:
        with self._lock:
            entry.status = "admitted"
        _metrics.counter(f"pool.admitted.{entry.kind}").inc()
        return entry

    def _next_entry(self, kind: str) -> Admission:
        with self._lock:
            self._order += 1
            return Admission(kind, self._order)

    # -- attestation admission ----------------------------------------------
    def admit_attestation(self, attestation) -> Admission:
        """Structural validation now, signature into the window (RLC) or
        verified inline (scalar twin). Returns the ticket."""
        if self.rlc:
            return self.admit_attestation_batch([attestation])[0]
        t0 = time.perf_counter()
        entry = Admission("attestation", 0)
        try:
            with trace.span("pool.admit", kind="attestation"):
                snap = self._head()
                if snap is None:
                    return self._reject(entry, "no_head")
                committee = self._attestation_structural(
                    entry, attestation, snap
                )
                if committee is None:
                    return entry
                # the per-message twin rejects pool redundancy BEFORE
                # any cryptography (the batched engine resolves the same
                # verdicts at settle time, in admission order)
                verdict = self.pool.classify_attestation(
                    entry.key, len(committee), list(entry.bits),
                    scalar=True,
                )
                if verdict != "new":
                    return self._reject(entry, verdict)
                with self._lock:
                    self._order += 1
                    entry.order = self._order
                return self._admit_scalar_attestation(entry, snap,
                                                      committee)
        finally:
            _metrics.histogram("pool.admit_s").observe(
                time.perf_counter() - t0
            )

    def admit_attestation_batch(self, attestations) -> "list[Admission]":
        """Admit a gossip batch: per-message structural validation, the
        signature claims deferred into the window — ONE span, one lock
        cycle, and at most one flush dispatch per filled window for the
        whole batch, so the per-message admission cost approaches the
        field-adds that fold its claim into the running batch. Dedup
        against the pool resolves at settle time in admission order,
        giving verdicts bit-identical to the per-message twin's."""
        if not self.rlc:
            return [self.admit_attestation(a) for a in attestations]
        t0 = time.perf_counter()
        entries: list = []
        accepted: list = []
        with trace.span("pool.admit", kind="attestation",
                        batch=len(attestations)):
            snap = self._head()
            for att in attestations:
                entry = Admission("attestation", 0)
                entries.append(entry)
                if snap is None:
                    self._reject(entry, "no_head")
                    continue
                committee = self._attestation_structural(entry, att, snap)
                if committee is None:
                    continue
                rc, raw, is_inf = self._g2_parse(entry.sig_bytes)
                if rc != 0:
                    self._reject(entry, "malformed")
                    continue
                if is_inf:
                    self._reject(entry, "signature")
                    continue
                entry.sig_raw = raw
                entry.committee_ref = self._committee_record(
                    snap, entry.key, committee
                )
                accepted.append(entry)
            dispatches = []
            with self._lock:
                for entry in accepted:
                    self._order += 1
                    entry.order = self._order
                    self._window.append(entry)
                    if len(self._window) >= self.window_size:
                        dispatches.append(self._window)
                        self._window = []
                _metrics.gauge("pool.window_pending").set(
                    len(self._window)
                )
            for window in dispatches:
                self._dispatch(window)
        elapsed = time.perf_counter() - t0
        _metrics.histogram("pool.admit_s").observe(elapsed)
        _metrics.counter("pool.admit_batches").inc()
        return entries

    def _g2_parse(self, sig_bytes: bytes):
        from ..native import bls as native_bls

        return native_bls.g2_decompress(sig_bytes, check_subgroup=False)

    def _attestation_structural(self, entry: Admission, att, snap):
        """The gossip-validation checks shared verbatim by both engines
        (structural order IS the rejection-reason contract). Fills the
        entry and returns the committee, or rejects and returns None."""
        from ..models.phase0 import helpers as h

        context = self.context
        try:
            data = att.data
            slot = int(data.slot)
            bit_list = [bool(b) for b in att.aggregation_bits]
        except Exception:  # noqa: BLE001 — not attestation-shaped
            self._reject(entry, "malformed")
            return None
        head_slot = int(snap.slot)
        if not bit_list or not any(bit_list):
            self._reject(entry, "malformed")
            return None
        if slot > head_slot:
            self._reject(entry, "future_slot")
            return None
        if slot + int(context.SLOTS_PER_EPOCH) < head_slot:
            self._reject(entry, "expired")
            return None
        target_epoch = int(data.target.epoch)
        if target_epoch != h.compute_epoch_at_slot(slot, context):
            self._reject(entry, "invalid")
            return None
        committee_bits = getattr(att, "committee_bits", None)
        if committee_bits is not None:  # electra EIP-7549 shape
            if int(data.index) != 0:
                self._reject(entry, "invalid")
                return None
            committee_indices = [
                i for i, b in enumerate(committee_bits) if b
            ]
            if not committee_indices:
                self._reject(entry, "malformed")
                return None
            committee_key = tuple(committee_indices)
        else:
            committee_indices = [int(data.index)]
            committee_key = int(data.index)
        count = snap.memo(
            ("pool_committee_count", target_epoch),
            lambda: h.get_committee_count_per_slot(
                snap.raw, target_epoch, context
            ),
        )
        if any(ci >= count for ci in committee_indices):
            self._reject(entry, "unknown_committee")
            return None
        committee: list = []
        for ci in committee_indices:
            committee.extend(
                snap.memo(
                    ("pool_committee", slot, ci),
                    lambda ci=ci: tuple(
                        h.get_beacon_committee(snap.raw, slot, ci, context)
                    ),
                )
            )
        if len(bit_list) != len(committee):
            self._reject(entry, "bits_mismatch")
            return None

        # hash-consed data root: gossip repeats the same AttestationData
        # across many aggregators, so the merkleization runs once per
        # DISTINCT data (keyed by its serialization, which is cheaper
        # than the tree walk)
        data_ser = bytes(type(data).serialize(data))
        data_root = self._data_roots.get(data_ser)
        if data_root is None:
            data_root = bytes(type(data).hash_tree_root(data))
            with self._lock:
                if len(self._data_roots) >= 4096:
                    self._data_roots = {}
                self._data_roots[data_ser] = data_root
        domain = snap.memo(
            ("pool_att_domain", target_epoch),
            lambda: bytes(
                h.get_domain(
                    snap.raw, _attester_domain_type(), target_epoch,
                    context,
                )
            ),
        )
        entry.key = (slot, committee_key, data_root)
        entry.bits = tuple(bit_list)
        entry.indices = [committee[i] for i, b in enumerate(bit_list) if b]
        # the signing root of SSZ SigningData(object_root, domain) is
        # exactly hash(object_root || domain) — two 32-byte chunks, one
        # compression (asserted against compute_signing_root in tests)
        entry.msg_root = _sha256(data_root + domain)
        entry.sig_bytes = bytes(att.signature)
        entry.container = att
        entry.snap = snap
        return committee

    def _admit_scalar_attestation(self, entry, snap, committee) -> Admission:
        """The per-message twin: parse every key, parse the signature,
        one pairing pair — then insert. The naive gossip validator."""
        validators = snap.raw.validators
        try:
            sig = bls.Signature.from_bytes(entry.sig_bytes)
        except Exception:  # noqa: BLE001 — unparseable point
            return self._reject(entry, "malformed")
        try:
            keys = [
                bls.PublicKey.from_bytes(bytes(validators[i].public_key))
                for i in entry.indices
            ]
        except Exception:  # noqa: BLE001 — registry keys are valid; this
            return self._reject(entry, "malformed")  # guards exotic states
        if not bls.fast_aggregate_verify(keys, entry.msg_root, sig):
            return self._reject(entry, "signature")
        return self._finalize_attestation(entry)

    def _finalize_attestation(self, entry: Admission) -> Admission:
        """Insert a signature-verified aggregate + record its votes (the
        equivocation ledger may surface a slashing). The insert's own
        locked re-classification is the redundancy verdict — one vector
        pass covers both the race guard and in-order settle dedup."""
        row, verdict = self.pool.insert_attestation(
            entry.key, len(entry.bits), list(entry.bits),
            entry.sig_bytes, entry.container, scalar=not self.rlc,
        )
        if row is None:
            return self._reject(entry, verdict)
        builder = self._builder(entry.snap.fork)
        surfaced = self.pool.note_votes(
            entry.indices, entry.container.data,
            entry.key[2], entry.sig_bytes, builder,
        )
        for _ in surfaced:
            trace.event("pool.slashing_surfaced",
                        slot=entry.key[0])
        return self._admit(entry)

    def _committee_record(self, snap, key, committee) -> list:
        """[committee, pk objects, raws|None] for the fused flush,
        cached per (snapshot root, slot, committee key)."""
        cache_key = (snap.root, key[0], key[1])
        with self._lock:
            record = self._committees.get(cache_key)
            if record is not None:
                return record
        validators = snap.raw.validators
        objs = [
            bls.PublicKey.from_validated_bytes(
                bytes(validators[i].public_key)
            )
            for i in committee
        ]
        record = [tuple(committee), objs, None]
        with self._lock:
            if len(self._committees) >= 1024:
                self._committees = {}
            self._committees.setdefault(cache_key, record)
            record = self._committees[cache_key]
        return record

    # -- singleton-op admission ----------------------------------------------
    def admit_voluntary_exit(self, signed_exit) -> Admission:
        return self._admit_op("voluntary_exit", signed_exit,
                              "process_voluntary_exit")

    def admit_proposer_slashing(self, slashing) -> Admission:
        return self._admit_op("proposer_slashing", slashing,
                              "process_proposer_slashing")

    def admit_attester_slashing(self, slashing) -> Admission:
        return self._admit_op("attester_slashing", slashing,
                              "process_attester_slashing")

    def admit_bls_change(self, signed_change) -> Admission:
        return self._admit_op("bls_change", signed_change,
                              "process_bls_to_execution_change")

    def _admit_op(self, kind: str, container, processor_name: str) -> Admission:
        """Run the fork's OWN processor on the snapshot's scratch state
        inside a signature-collection scope: structural checks are the
        spec's, the collected sets defer into the window (RLC) or verify
        inline (scalar twin)."""
        t0 = time.perf_counter()
        entry = self._next_entry(kind)
        try:
            with trace.span("pool.admit", kind=kind):
                return self._admit_op_inner(entry, container, processor_name)
        finally:
            _metrics.histogram("pool.admit_s").observe(
                time.perf_counter() - t0
            )

    def _admit_op_inner(self, entry, container, processor_name) -> Admission:
        snap = self._head()
        if snap is None:
            return self._reject(entry, "no_head")
        if self._op_is_duplicate(entry.kind, container):
            return self._reject(entry, "duplicate")
        bp = self._fork_module(snap.fork).block_processing
        processor = getattr(bp, processor_name, None)
        if processor is None:  # e.g. BLS change before capella
            return self._reject(entry, "invalid")
        scratch = self._op_scratch(snap)
        entry.container = container
        entry.snap = snap
        # the scratch mutates as ops admit (an exit initiates, a slashing
        # slashes) — deliberately: co-admitted ops validate sequentially,
        # exactly as they will execute in a produced block. One engine
        # lock scope serializes scratch access.
        with self._lock:
            try:
                with collect_signatures() as batch:
                    processor(scratch, container, self.context)
            except Error:
                reject_invalid = True
            else:
                reject_invalid = False
                entry.sets = list(batch.sets)
        if reject_invalid:
            return self._reject(entry, "invalid")
        if not self.rlc:
            for s in entry.sets:
                if not s.verify():
                    return self._reject(entry, "signature")
            return self._finalize_op(entry)
        entry.set_verdicts = []
        self._enqueue(entry)
        return entry

    def _op_is_duplicate(self, kind: str, container) -> bool:
        return self.pool.op_held(kind, container)

    def _op_scratch(self, snap):
        """This ENGINE's mutable validation state for ``snap`` (never
        shared: a parallel engine — the scalar differential twin — must
        see its own op sequence, not ours). Bounded: one live scratch;
        a head rotation drops the old one."""
        with self._lock:
            scratch = self._scratches.get(snap.root)
        if scratch is None:
            built = snap.raw.copy()
            with self._lock:
                if len(self._scratches) >= 2:
                    self._scratches = {}
                self._scratches.setdefault(snap.root, built)
                scratch = self._scratches[snap.root]
        return scratch

    def _finalize_op(self, entry: Admission) -> Admission:
        pool = self.pool
        inserted = {
            "voluntary_exit": pool.insert_voluntary_exit,
            "proposer_slashing": pool.insert_proposer_slashing,
            "attester_slashing": pool.insert_attester_slashing,
            "bls_change": pool.insert_bls_change,
        }[entry.kind](entry.container)
        if not inserted:
            return self._reject(entry, "duplicate")
        return self._admit(entry)

    # -- the RLC window ------------------------------------------------------
    def _enqueue(self, entry: Admission) -> None:
        dispatch = None
        with self._lock:
            self._window.append(entry)
            _metrics.gauge("pool.window_pending").set(len(self._window))
            if len(self._window) >= self.window_size:
                dispatch, self._window = self._window, []
        if dispatch:
            self._dispatch(dispatch)

    def flush(self) -> None:
        """Dispatch the current partial window (if any)."""
        with self._lock:
            dispatch, self._window = self._window, []
        if dispatch:
            self._dispatch(dispatch)

    def _dispatch(self, entries: list) -> None:
        with trace.span("pool.flush.dispatch", messages=len(entries)):
            # the window's causal handoff token: anchored here (under
            # the admitting span when the dispatch rode an admit call),
            # stamped onto every ticket, adopted by the verify lane and
            # the settle path — admission→settle is one connected tree
            ctx = trace.context()
            tid = ctx.trace_id if ctx is not None else None
            for e in entries:
                e.trace_id = tid
            entries = self._membership_cull(entries)
            sets, attribution = self._build_sets(entries)
            if sets:
                future = bls.verify_signature_sets_async(
                    sets,
                    timer=lambda s: _metrics.histogram(
                        "pool.flush_verify_s"
                    ).observe(s, trace_id=tid),
                    trace_ctx=ctx,
                )
            else:
                future = None
        _metrics.counter("pool.flushes").inc()
        _metrics.histogram("pool.flush_window_messages").observe(len(entries))
        _metrics.histogram("pool.flush_sets").observe(len(sets))
        settle_now = None
        with self._lock:
            self._inflight.append((future, sets, attribution, entries, ctx))
            _metrics.gauge("pool.window_pending").set(len(self._window))
            if len(self._inflight) > self.max_inflight:
                settle_now = self._inflight.pop(0)
        if settle_now is not None:
            self._settle_one(settle_now)

    def _membership_cull(self, entries: list) -> list:
        """Batched G2 subgroup membership for the window's attestation
        signature points: one blinded MSM + one checked round-trip. On
        failure, per-point checks cull the offenders as ``malformed``."""
        att = [e for e in entries if e.kind == "attestation"]
        if not att:
            return entries
        from ..native import bls as native_bls

        if len(att) == 1:  # a lone point just gets the direct check
            rc, _, _ = native_bls.g2_decompress(
                att[0].sig_bytes, check_subgroup=True
            )
            if rc != 0:
                self._reject(att[0], "malformed")
                return [e for e in entries if e is not att[0]]
            return entries
        points = b"".join(e.sig_raw for e in att)
        blinders = b"".join(
            _nonzero_scalar16().rjust(32, b"\x00") for _ in att
        )
        try:
            combined, is_inf = native_bls.g2_msm(points, blinders, len(att))
            rc, _, _ = native_bls.g2_decompress(
                native_bls.g2_compress_raw(combined, is_inf),
                check_subgroup=True,
            )
            membership_ok = rc == 0 and not is_inf
        except Exception:  # noqa: BLE001 — fall back to per-point checks
            membership_ok = False
        _metrics.counter("pool.membership_batches").inc()
        if membership_ok:
            return entries
        _metrics.counter("pool.membership_batch_failures").inc()
        survivors = []
        for e in entries:
            if e.kind != "attestation":
                survivors.append(e)
                continue
            rc, _, _ = native_bls.g2_decompress(
                e.sig_bytes, check_subgroup=True
            )
            if rc == 0:
                survivors.append(e)
            else:
                self._reject(e, "malformed")
        return survivors

    def _group_raws(self, record: list) -> list:
        """Materialize (once) the committee's raw affine pubkeys through
        the eight-wide bulk decompression."""
        if record[2] is None:
            bls.warm_raw_keys(record[1])
            raws = [pk.raw_uncompressed() for pk in record[1]]
            with self._lock:
                if record[2] is None:
                    record[2] = raws
        return record[2]

    def _build_sets(self, entries: list) -> "tuple[list, list]":
        """The window's fused signature sets + attribution:
        ``("group", [entries])`` for a fused attestation claim,
        ``("op", entry, k)`` for a singleton op's k-th collected set."""
        from ..native import bls as native_bls

        sets: list = []
        attribution: list = []
        groups: dict = {}
        for e in entries:
            if e.kind == "attestation":
                groups.setdefault((e.key, bytes(e.msg_root)), []).append(e)
        for (key, msg_root), members in sorted(
            groups.items(), key=lambda kv: (kv[0][0][0], str(kv[0][0][1]),
                                            kv[0][0][2], kv[0][1])
        ):
            fused = self._fused_set(members, msg_root, native_bls)
            if fused is None:
                # MSM trouble: verify members individually (split path)
                for m in members:
                    sets.append(self._member_set(m))
                    attribution.append(("group", [m]))
                continue
            sets.append(fused)
            attribution.append(("group", members))
        for e in entries:
            if e.kind == "attestation":
                continue
            for k, s in enumerate(e.sets):
                sets.append(s)
                attribution.append(("op", e, k))
        return sets, attribution

    def _fused_set(self, members: list, msg_root: bytes, native_bls):
        """One SignatureSet proving every member's claim at once:
        multiplicity-weighted G1 MSM over the committee for the key
        side, signature sum for the G2 side."""
        record = members[0].committee_ref
        committee = record[0]
        raws = self._group_raws(record)
        try:
            import numpy as np

            counts = np.array(
                [m.bits for m in members], dtype=np.uint32
            ).sum(axis=0).tolist()
        except Exception:  # noqa: BLE001 — numpy-less: scalar sum
            counts = [0] * len(committee)
            for m in members:
                for i, b in enumerate(m.bits):
                    if b:
                        counts[i] += 1
        nz = [i for i, c in enumerate(counts) if c]
        try:
            points = b"".join(raws[i] for i in nz)
            scalars = b"".join(
                counts[i].to_bytes(32, "big") for i in nz
            )
            agg_raw, is_inf = native_bls.g1_msm(points, scalars, len(nz))
            if is_inf:
                return None
            agg_pk = bls.PublicKey._from_valid_bytes(
                native_bls.g1_compress_raw(agg_raw)
            )
            agg_pk._raw = agg_raw
            ones = b"".join(
                (1).to_bytes(32, "big") for _ in members
            )
            sig_raw, sig_inf = native_bls.g2_msm(
                b"".join(m.sig_raw for m in members), ones, len(members)
            )
            merged_sig = bls.Signature._from_valid_bytes(
                native_bls.g2_compress_raw(sig_raw, sig_inf)
            )
        except Exception:  # noqa: BLE001 — degrade to the split path
            _metrics.counter("pool.fallback.fuse_failed").inc()
            return None
        _metrics.counter("pool.fused_groups").inc()
        return bls.SignatureSet([agg_pk], msg_root, merged_sig)

    def _member_set(self, entry: Admission):
        record = entry.committee_ref
        keys = [record[1][i] for i, b in enumerate(entry.bits) if b]
        return bls.SignatureSet(
            keys, entry.msg_root,
            bls.Signature._from_valid_bytes(entry.sig_bytes),
        )

    # -- settlement ----------------------------------------------------------
    def settle(self, flush: bool = True) -> None:
        """Drain every dispatched window (optionally flushing the
        partial one first) and resolve all tickets."""
        if flush:
            self.flush()
        while True:
            with self._lock:
                if not self._inflight:
                    return
                item = self._inflight.pop(0)
            self._settle_one(item)

    def _settle_one(self, item) -> None:
        future, sets, attribution, entries, ctx = item
        verdicts = future.result() if future is not None else []
        # the settle span joins the window's causal tree (adopting the
        # context anchored at its dispatch span)
        with trace.adopt(ctx), \
                trace.span("pool.flush.settle", messages=len(entries)):
            # sig_ok writes are settle-private: a window settles exactly
            # once (popped under the engine lock), so its entries have
            # one writer here; callers read only the status field
            for (tag, *rest), verdict in zip(attribution, verdicts):
                if tag == "group":
                    members = rest[0]
                    if verdict:
                        for m in members:
                            m.sig_ok = True
                    else:
                        # split: re-verify each member's own claim so
                        # only the offenders reject — exact blame
                        _metrics.counter("pool.flush_splits").inc()
                        for m in members:
                            m.sig_ok = self._member_set(m).verify()
                else:
                    entry, _k = rest
                    entry.set_verdicts.append(bool(verdict))
            # resolve tickets in ADMISSION order: in-window redundancy
            # (a duplicate of a not-yet-settled aggregate) resolves
            # exactly as the scalar twin's message-by-message pool would
            for entry in sorted(
                (e for e in entries if e.status == "pending"),
                key=lambda e: e.order,
            ):
                if entry.kind == "attestation":
                    if entry.sig_ok:
                        # the insert's locked classify IS the in-order
                        # redundancy verdict (duplicate/subset reject
                        # inside _finalize)
                        self._finalize_attestation(entry)
                    else:
                        # a failed signature still reports redundancy
                        # FIRST — the per-message twin never reaches
                        # the pairing for a duplicate/subset
                        verdict = self.pool.classify_attestation(
                            entry.key, len(entry.bits), list(entry.bits)
                        )
                        self._reject(
                            entry,
                            "signature" if verdict == "new" else verdict,
                        )
                else:
                    if entry.set_verdicts is not None and all(
                        entry.set_verdicts
                    ) and len(entry.set_verdicts) == len(entry.sets):
                        self._finalize_op(entry)
                    else:
                        self._reject(entry, "signature")
        if ctx is not None:
            # one settled pool window = one linked trace: count it and
            # feed the slow-trace ring (dispatch capture → settle done)
            _metrics.counter("trace.windows_linked").inc()
            trace.note_trace(
                ctx, "pool.window",
                max(0.0, time.perf_counter() - ctx.ts),
                messages=len(entries), sets=len(sets),
            )

    # -- introspection -------------------------------------------------------
    def snapshot(self) -> dict:
        with self._lock:
            return {
                "rlc": self.rlc,
                "window_size": self.window_size,
                "window_pending": len(self._window),
                "inflight_windows": len(self._inflight),
            }


def _attester_domain_type():
    from ..domains import DomainType

    return DomainType.BEACON_ATTESTER


def _sha256(data: bytes) -> bytes:
    return hashlib.sha256(data).digest()


def _nonzero_scalar16() -> bytes:
    while True:
        s = secrets.token_bytes(16)
        if any(s):
            return s
