"""ethereum_consensus_tpu — a TPU-native Ethereum beacon-chain consensus
framework.

A ground-up reimplementation of the capabilities of
`ralexstokes/ethereum_consensus` (the Rust reference surveyed in SURVEY.md)
designed for TPUs: spec logic is host Python with exact u64 semantics; the
hot paths — SHA-256 merkleization, batched BLS aggregate verification,
shuffling, and per-validator epoch sweeps — run as JAX/XLA/Pallas kernels
sharded over device meshes.

Layout:
  ssz/       SSZ type algebra, codec, merkleization (replaces ssz_rs)
  crypto/    BLS12-381 + KZG (replaces blst/c-kzg) with oracle + device paths
  models/    per-fork spec modules (phase0..electra) + polymorphic types
  ops/       JAX/Pallas device kernels (sha256, merkle, shuffle, sweeps)
  parallel/  mesh construction, shard_map distributed reductions
  config/    presets, network configs, Context, networks
  utils/     clock, serde presentation helpers, math
  api/       Beacon-API client
  cli/       `ec`-equivalent CLI (keys, keystores, blobs)
"""

__version__ = "0.1.0"

from . import error, fork, primitives, ssz  # noqa: F401
from .fork import Fork  # noqa: F401


def __getattr__(name):
    # heavyweight subsystems load lazily so `import ethereum_consensus_tpu`
    # stays cheap (models pulls crypto + every fork's containers)
    import importlib

    if name in {
        "api", "builder", "cli", "clock", "config", "crypto", "executor", "execution_engine",
        "models", "networking", "ops", "parallel", "serde", "signing", "types",
        "utils",
    }:
        if name == "clock":
            return importlib.import_module(".utils.clock", __name__)
        return importlib.import_module(f".{name}", __name__)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
