"""Executor — polymorphic block application with inline cross-fork upgrades.

Reference parity: ethereum-consensus/src/state_transition/executor.rs:8-532
— ``apply_block`` dispatches on the block's fork, advancing the state
through every intermediate fork boundary (process_slots to the fork slot,
then upgrade_to_X, executor.rs:210-302), including the corner where the
block sits exactly on the upgrade slot (state_transition_block_in_slot,
executor.rs:215-224). Unlike the reference (phase0..deneb,
executor.rs:155-172), electra is supported.

Beyond the reference: ``stream`` replays an iterable of blocks through
the chain pipeline (pipeline/engine.py) — speculative host application
overlapped with windowed cross-block signature verification — with
observable semantics identical to an ``apply_block`` loop.
"""

from __future__ import annotations

from .utils import trace
from .error import IncompatibleForksError
from .fork import Fork
from .models.transition import Validation
from .types import FORK_SEQUENCE, BeaconState, SignedBeaconBlock, fork_module

__all__ = ["Executor", "Validation"]

_UPGRADE_FN = {
    Fork.ALTAIR: "upgrade_to_altair",
    Fork.BELLATRIX: "upgrade_to_bellatrix",
    Fork.CAPELLA: "upgrade_to_capella",
    Fork.DENEB: "upgrade_to_deneb",
    Fork.ELECTRA: "upgrade_to_electra",
}


class Executor:
    """Owns a polymorphic ``BeaconState`` + ``Context`` (executor.rs:8)."""

    def __init__(self, state: BeaconState, context):
        if not isinstance(state, BeaconState):
            state = BeaconState.wrap(state, context.preset)
        self.state = state
        self.context = context

    def apply_block(self, signed_block) -> None:
        """(executor.rs:113)"""
        with trace.span(
            "executor.apply_block", slot=int(signed_block.message.slot)
        ):
            self.apply_block_with_validation(signed_block, Validation.ENABLED)

    def apply_block_with_validation(self, signed_block, validation) -> None:
        """(executor.rs:135)"""
        if not isinstance(signed_block, SignedBeaconBlock):
            signed_block = SignedBeaconBlock.wrap(signed_block, self.context.preset)

        source = self.state.version()
        destination = signed_block.version()
        if destination < source:
            raise IncompatibleForksError(destination, source)

        state = self.state.data
        fork = source
        # advance through each intermediate fork boundary
        # (executor.rs:210-302): slots to the fork slot under the old fork's
        # rules, then the upgrade function
        for next_fork in FORK_SEQUENCE[source + 1 : destination + 1]:
            fork_slot = (
                self.context.fork_activation_epoch(next_fork)
                * self.context.SLOTS_PER_EPOCH
            )
            if state.slot < fork_slot:
                fork_module(fork).slot_processing.process_slots(
                    state, fork_slot, self.context
                )
            upgrade = getattr(fork_module(next_fork), _UPGRADE_FN[next_fork])
            state = upgrade(state, self.context)
            fork = next_fork

        transition = fork_module(destination).state_transition
        if fork != source and signed_block.data.message.slot == state.slot:
            # block lands exactly on the upgrade slot (executor.rs:215-224)
            transition.state_transition_block_in_slot(
                state, signed_block.data, validation, self.context
            )
        else:
            transition.state_transition(
                state, signed_block.data, self.context, validation
            )

        self.state = BeaconState.from_fork(destination, state)

    def stream(
        self,
        signed_blocks,
        policy=None,
        validation: Validation = Validation.ENABLED,
        stats=None,
    ):
        """Apply an iterable of signed blocks through the chain pipeline
        (``pipeline.ChainPipeline``): speculative host application
        overlapped with windowed cross-block signature verification on a
        background worker. Returns the run's ``PipelineStats``.

        Observable semantics match a loop of ``apply_block``: the same
        final state bit-for-bit on success; on an invalid block, the same
        structured error raises and ``self.state`` is the last state
        whose signatures fully verified (not mid-block garbage)."""
        from .pipeline import ChainPipeline

        pipeline = ChainPipeline(
            self, policy=policy, validation=validation, stats=stats
        )
        for signed_block in signed_blocks:
            pipeline.submit(signed_block)
        return pipeline.close()
