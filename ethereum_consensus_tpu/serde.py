"""Presentation-serde helpers: the hex / decimal-string JSON conventions.

Reference parity: ethereum-consensus/src/serde.rs (238 LoC) — `as_hex`
(0x-prefixed byte strings), `as_str` (u64 as decimal string, the
consensus-specs JSON convention), `seq_of_str` (sequences thereof). The SSZ
descriptors' to_json/from_json already apply these conventions per type;
these helpers are for ad-hoc values (API payloads, YAML configs).
"""

from __future__ import annotations

__all__ = [
    "as_hex",
    "from_hex",
    "as_str",
    "from_str",
    "seq_of_str",
    "seq_from_str",
]


def as_hex(data: bytes) -> str:
    """bytes → "0x..." (serde.rs as_hex::serialize)."""
    return "0x" + bytes(data).hex()


def from_hex(text: str, expected_length: int | None = None) -> bytes:
    """"0x..." → bytes; enforces length when given (serde.rs try_bytes_from_hex_str)."""
    if not isinstance(text, str) or not text.startswith("0x"):
        raise ValueError(f"expected 0x-prefixed hex string, got {text!r}")
    data = bytes.fromhex(text[2:])
    if expected_length is not None and len(data) != expected_length:
        raise ValueError(
            f"expected {expected_length} bytes, decoded {len(data)} from {text!r}"
        )
    return data


def as_str(value: int) -> str:
    """u64 → decimal string (serde.rs as_str::serialize)."""
    return str(int(value))


def from_str(text) -> int:
    """decimal string (or int for lenient inputs) → u64 (serde.rs as_str)."""
    value = int(text)
    if not 0 <= value < 2**64:
        raise ValueError(f"{value} out of u64 range")
    return value


def seq_of_str(values) -> list[str]:
    """sequence of u64 → decimal strings (serde.rs seq_of_str)."""
    return [as_str(v) for v in values]


def seq_from_str(texts) -> list[int]:
    return [from_str(t) for t in texts]
